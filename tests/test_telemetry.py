"""Flight recorder + telemetry (PR 2): default-off is FREE, on is neutral.

Three contracts guard this layer:

1. **Default-off is free**: with telemetry disabled (the default) the state's
   ``telemetry`` leaf is ``None`` (pruned from the pytree), schedules are
   BIT-IDENTICAL to the pre-telemetry build (the PR-1 golden digests of
   tests/test_gray.py, re-pinned here), and config fingerprints are unchanged
   so recorded artifacts (BENCH_SWEEP.json, checkpoints) keep matching.
2. **On is outcome-neutral**: telemetry draws NO randomness — it is computed
   from signals the tick already produced — so enabling it must leave the
   protocol schedule bit-identical on BOTH engines, and the fused Pallas
   kernel must carry the recorder arrays bit-exactly vs its XLA reference.
3. **The recorder tells the truth**: counters match independent reductions,
   the ring decodes to a wrap-ordered per-lane timeline, the histogram
   buckets decide ticks, and a corrupt-config shrink repro's timeline names
   the injected corruption ticks.
"""

import dataclasses
import hashlib
import json

import jax
import jax.numpy as jnp
import pytest

from paxos_tpu.core import telemetry as T
from paxos_tpu.harness import config as C
from paxos_tpu.harness.run import (
    base_key,
    get_step_fn,
    init_plan,
    init_state,
    run,
    run_chunk,
)

TEL = T.TelemetryConfig(counters=True, ring_depth=16, hist_bins=8)


def _digest(state) -> str:
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(state):
        h.update(jax.device_get(leaf).tobytes())
    return h.hexdigest()[:16]


def _xla_final(cfg, n_ticks=32):
    return run_chunk(
        init_state(cfg), base_key(cfg), init_plan(cfg), cfg.fault, n_ticks,
        get_step_fn(cfg.protocol),
    )


def _ctr_final(cfg, n_ticks=32):
    from paxos_tpu.kernels.fused_tick import fused_fns, reference_chunk

    apply_fn, mask_fn, _ = fused_fns(cfg.protocol)
    return reference_chunk(
        init_state(cfg), cfg.seed, init_plan(cfg), cfg.fault, n_ticks,
        apply_fn=apply_fn, mask_fn=mask_fn, blk_id=0,
    )


# The PR-1 goldens (tests/test_gray.py, n_inst=256, seed=7, 32 ticks, CPU):
# recorder-off must reproduce them, and recorder-ON minus the telemetry
# leaf must reproduce them too (schedule unperturbed).
_GOLDEN_XLA = {
    "config2": (lambda: C.config2_dueling_drop(256, 7), "83347bc41b16a2aa"),
    "config3": (lambda: C.config3_multipaxos(256, 7), "93a2dd9d7b8d66e4"),
    "fastpaxos": (lambda: C.config5_sweep(256, 7)[1], "c43658973b29e73e"),
    "raftcore": (lambda: C.config5_sweep(256, 7)[2], "4662db6b2c5a39d3"),
}
_GOLDEN_CTR = {
    "config2": (lambda: C.config2_dueling_drop(256, 7), "db6db6f40f16eb7b"),
    "config3": (lambda: C.config3_multipaxos(256, 7), "4b6525460815d9c5"),
    "fastpaxos": (lambda: C.config5_sweep(256, 7)[1], "72beea3ccdacab94"),
    "raftcore": (lambda: C.config5_sweep(256, 7)[2], "eb285905571b709f"),
}


# One representative per state-shape family stays in the fast lane; the
# remaining protocols are exhaustive coverage (-m slow, full-suite lane).
_FAST_XLA = ("config2", "config3")
_FAST_CTR = ("config2",)


@pytest.mark.parametrize(
    "name",
    [
        n if n in _FAST_XLA else pytest.param(n, marks=pytest.mark.slow)
        for n in sorted(_GOLDEN_XLA)
    ],
)
def test_recorder_on_schedule_identical_xla(name):
    mk, want = _GOLDEN_XLA[name]
    assert _digest(_xla_final(mk())) == want  # off == pre-telemetry golden
    fin = _xla_final(dataclasses.replace(mk(), telemetry=TEL))
    assert fin.telemetry is not None
    assert _digest(fin.replace(telemetry=None)) == want  # on == same schedule


@pytest.mark.parametrize(
    "name",
    [
        n if n in _FAST_CTR else pytest.param(n, marks=pytest.mark.slow)
        for n in sorted(_GOLDEN_CTR)
    ],
)
def test_recorder_on_schedule_identical_counter_stream(name):
    mk, want = _GOLDEN_CTR[name]
    assert _digest(_ctr_final(mk())) == want
    fin = _ctr_final(dataclasses.replace(mk(), telemetry=TEL))
    assert _digest(fin.replace(telemetry=None)) == want


def test_default_off_prunes_to_none():
    """Disabled telemetry leaves NO trace in the pytree (structure parity)."""
    for mk in (C.config1_no_faults, C.config3_multipaxos):
        cfg = mk(64, 0)
        state = init_state(cfg)
        assert state.telemetry is None
        on = init_state(dataclasses.replace(cfg, telemetry=TEL))
        off_n = len(jax.tree_util.tree_leaves(state))
        on_n = len(jax.tree_util.tree_leaves(on))
        # counters + ring + cursor + seq + hist
        assert on_n == off_n + 5
        # All recorder leaves are non-scalar int32 — the fused engine's
        # generic flattening rides them through with no kernel changes.
        for leaf in jax.tree_util.tree_leaves(on.telemetry):
            assert leaf.dtype == jnp.int32 and leaf.ndim >= 1


def test_fingerprint_unchanged_by_default_telemetry():
    """Pre-telemetry artifacts must keep matching: with the default (off)
    telemetry the fingerprint is computed WITHOUT the telemetry key — the
    exact pre-PR config shape plus the packed-layout version key (which is
    deliberately fingerprinted: a layout change re-keys every checkpoint);
    non-default telemetry IS fingerprinted."""
    import hashlib

    from paxos_tpu.utils.bitops import layout_version

    cfg = C.config2_dueling_drop(1 << 20)
    d = dataclasses.asdict(cfg)
    del d["telemetry"]  # the pre-telemetry asdict shape
    del d["coverage"]  # default-off coverage is likewise dropped (PR 8)
    del d["exposure"]  # ... and default-off exposure (PR 9)
    del d["margin"]  # ... and default-off margin (PR 12)
    del d["workload"]  # ... and default-off workload (PR 20)
    d["layout_version"] = layout_version(cfg.protocol)
    pre = hashlib.sha256(
        json.dumps(d, sort_keys=True).encode()
    ).hexdigest()[:16]
    assert cfg.fingerprint() == pre
    assert (
        dataclasses.replace(cfg, telemetry=TEL).fingerprint()
        != cfg.fingerprint()
    )


@pytest.mark.parametrize(
    "protocol",
    [
        "paxos",
        pytest.param("multipaxos", marks=pytest.mark.slow),
        pytest.param("fastpaxos", marks=pytest.mark.slow),
        pytest.param("raftcore", marks=pytest.mark.slow),
    ],
)
def test_fused_kernel_carries_recorder_bitexact(protocol):
    """fused_chunk(interpret) == reference_chunk with the recorder ON."""
    from paxos_tpu.kernels.fused_tick import FUSED_CHUNKS, fused_fns, reference_chunk
    from paxos_tpu.utils.trees import tree_mismatches

    base = {
        "paxos": C.config2_dueling_drop,
        "multipaxos": C.config3_multipaxos,
        "fastpaxos": lambda n, s: C.config5_sweep(n, s)[1],
        "raftcore": lambda n, s: C.config5_sweep(n, s)[2],
    }[protocol](64, 7)
    cfg = dataclasses.replace(base, telemetry=TEL)
    apply_fn, mask_fn, _ = fused_fns(cfg.protocol)
    plan = init_plan(cfg)
    sr = reference_chunk(
        init_state(cfg), jnp.int32(cfg.seed), plan, cfg.fault, 24,
        apply_fn=apply_fn, mask_fn=mask_fn,
    )
    sp = FUSED_CHUNKS[cfg.protocol](
        init_state(cfg), jnp.int32(cfg.seed), plan, cfg.fault, 24,
        block=64, interpret=True,
    )
    assert tree_mismatches(sp, sr) == []
    assert int(sp.telemetry.seq.max()) > 0  # the recorder really recorded


def test_counters_match_independent_reductions():
    """decide count == chosen lanes; histogram total == decide total."""
    cfg = dataclasses.replace(
        C.config2_dueling_drop(256, 7), telemetry=TEL
    )
    fin = _xla_final(cfg, n_ticks=48)
    rep = T.telemetry_report(fin.telemetry)
    chosen = int(jax.device_get(fin.learner.chosen).sum())
    assert rep["counters"]["decide"] == chosen
    assert sum(rep["hist"]) == chosen
    assert rep["counters"]["conflict"] == int(
        jax.device_get(fin.learner.violations).sum()
    )
    # No partitions/corruption/dup configured -> those counters stay zero.
    for ev in ("corrupt", "dup", "part_cut", "part_heal", "recover"):
        assert rep["counters"][ev] == 0
    # Ring words: at most one per (lane, tick), at least one per decide.
    assert chosen <= rep["events_recorded"] <= 256 * 48


def test_ring_decode_wrap_order():
    """Per-lane decode is tick-ordered and keeps only the last D events."""
    cfg = dataclasses.replace(
        C.config2_dueling_drop(64, 7),
        telemetry=T.TelemetryConfig(counters=True, ring_depth=4),
    )
    fin = _xla_final(cfg, n_ticks=32)
    for lane in (0, 13, 63):
        tl = T.decode_lane(fin.telemetry, lane)
        assert len(tl) <= 4
        ticks = [e["tick"] for e in tl]
        assert ticks == sorted(ticks)
        assert all(e["events"] for e in tl)
        seq = int(jax.device_get(fin.telemetry.seq)[lane])
        if seq > 4:  # wrapped: decoded window is the LAST writes
            assert len(tl) == 4


def test_decode_word_layout():
    word = (1 << (T.EVENT_SHIFT + T.EVENTS.index("decide"))) | 37
    rec = T.decode_word(word)
    assert rec == {"tick": 37, "events": ["decide"]}


def test_part_cut_heal_recover_recorded():
    """Partition windows and crash recoveries land in the counters."""
    cfg = dataclasses.replace(C.config_partition(256, 3), telemetry=TEL)
    rep = run(cfg, total_ticks=96, chunk=32)
    tel = rep["telemetry"]["counters"]
    assert tel["part_cut"] > 0
    assert tel["part_heal"] > 0
    cfg3 = dataclasses.replace(C.config3_multipaxos(256, 7), telemetry=TEL)
    rep3 = run(cfg3, total_ticks=64, chunk=32)
    assert rep3["telemetry"]["counters"]["recover"] > 0


def test_run_report_embeds_telemetry():
    cfg = dataclasses.replace(C.config1_no_faults(64, 0), telemetry=TEL)
    rep = run(cfg, total_ticks=16, chunk=8)
    assert rep["telemetry"]["counters"]["decide"] == 64
    assert rep["telemetry"]["hist_ticks_per_bin"] == T.HIST_TICKS_PER_BIN
    # And with the default config the report has NO telemetry block.
    rep_off = run(C.config1_no_faults(64, 0), total_ticks=16, chunk=8)
    assert "telemetry" not in rep_off


def test_corrupt_shrink_timeline_names_corruption_tick():
    """Acceptance: a corrupt-config repro ships a decoded event timeline
    whose victim lane names the injected corruption ticks."""
    from paxos_tpu.harness.shrink import shrink

    res = shrink(C.config_corrupt(256, 0), max_ticks=64, chunk=32)
    assert res is not None
    assert res.timeline, "repro must carry a decoded timeline"
    corrupt_ticks = [
        e["tick"] for e in res.timeline if "corrupt" in e["events"]
    ]
    assert corrupt_ticks, "timeline must name the injected corruption"
    assert res.to_json()["timeline"] == res.timeline
    # The timeline rides the repro JSON end-to-end.
    json.dumps(res.to_json())
    # The causal reading rides too: reconstructed round spans whose fault
    # annotations name the same corruption ticks the raw timeline does.
    assert res.spans, "repro must carry reconstructed round spans"
    span_corrupt_ticks = sorted(
        f["tick"] for s in res.spans for f in s.faults
        if f["kind"] == "corrupt"
    )
    assert span_corrupt_ticks == sorted(corrupt_ticks)
    assert res.to_json()["spans"] == [s.to_json() for s in res.spans]


def test_hist_saturation_flags_overflow():
    """The histogram's last bin is a catch-all; decoding must SAY when it
    caught anything instead of letting the tail read as a real bin."""
    # Flag semantics: <2 bins have no in-range bins to misread.
    assert T.hist_saturation([]) == {"overflow": 0, "saturated": False}
    assert T.hist_saturation([7]) == {"overflow": 0, "saturated": False}
    assert T.hist_saturation([3, 0]) == {"overflow": 0, "saturated": False}
    assert T.hist_saturation([3, 2]) == {"overflow": 2, "saturated": True}

    # A 2-bin histogram under dueling proposers (decides routinely past
    # tick 8) must report a clipped tail end-to-end.
    cfg = dataclasses.replace(
        C.config2_dueling_drop(64, 3),
        telemetry=T.TelemetryConfig(counters=True, hist_bins=2),
    )
    state = _xla_final(cfg, n_ticks=32)
    counts, sat = T.hist_totals(state.telemetry, with_saturation=True)
    assert T.hist_totals(state.telemetry) == counts  # default unchanged
    assert sat == T.hist_saturation(counts)
    rep = T.telemetry_report(state.telemetry)
    assert rep["hist"] == counts
    assert rep["hist_overflow"] == counts[-1]
    assert rep["hist_saturated"] is (counts[-1] > 0)


def test_checkpoint_roundtrip_with_recorder(tmp_path):
    """A telemetry-enabled campaign checkpoints and resumes losslessly."""
    from paxos_tpu.harness import checkpoint as ckpt

    cfg = dataclasses.replace(C.config2_dueling_drop(64, 5), telemetry=TEL)
    state = _xla_final(cfg, n_ticks=16)
    plan = init_plan(cfg)
    ckpt.save(tmp_path / "snap", state, plan, cfg, engine="xla")
    state2, plan2, cfg2 = ckpt.restore(tmp_path / "snap", engine="xla")
    assert cfg2.telemetry == cfg.telemetry
    from paxos_tpu.utils.trees import tree_mismatches

    assert tree_mismatches(jax.device_get(state), state2) == []
