"""Client-workload plane (PR 20): off is free, on draws ONE stream, honest.

Four contracts guard the workload plane (the margin plane's template, with
one twist — unlike the pure observers this plane legitimately consumes
randomness, so "neutral" means *exactly the arrival draw and nothing
else*):

1. **Default-off is free**: with the workload off (the default) the
   state's ``wload`` leaf is ``None`` (pruned pytree, zero arrival PRNG
   draws), schedules are BIT-IDENTICAL to the established golden digests
   (re-pinned from tests/test_margin.py), and the default config
   fingerprint is unchanged so recorded artifacts keep matching.
2. **On perturbs nothing else**: the arrival draw rides its own
   registered fold/stream (``ARRIVAL_BITS`` / ``ARRIVAL``), so a
   workload-on state minus its ``wload`` leaf reproduces the SAME golden
   digests on BOTH engines — the protocol schedule never moves.
3. **The queue is honest (the oracle)**: over a 256-tick campaign the
   device leaves equal an independent host-side numpy replay —
   re-deriving the per-tick arrival bits straight from the stream
   registry (never from the device) and replaying the ring/histogram in
   ``np_replay_queue`` — exactly, per lane, on both engines.  The fused
   Pallas kernel carries the queue bit-exact vs its XLA reference via
   the generic packed-word passthrough.
4. **The plumbing round-trips**: checkpoints restore the workload config
   and queue arrays bit-exact (pre-workload snapshots default off), run
   reports embed the SLO block, and the slo reductions (percentiles,
   breach gating, overload knee, cross-seed merge) are pinned.
"""

import dataclasses
import hashlib
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paxos_tpu.core import streams as streams_mod
from paxos_tpu.harness import checkpoint
from paxos_tpu.harness import config as C
from paxos_tpu.harness.run import (
    base_key,
    get_step_fn,
    init_plan,
    init_state,
    run,
    run_chunk,
)
from paxos_tpu.obs import slo as slo_mod
from paxos_tpu.workload import generator as gen

# The oracle/golden workload: mixed classes (all three arrival arms live),
# a rate high enough to exercise the ring and a cap low enough to shed.
WL = gen.WorkloadConfig(mix="mixed", rate=0.2, burst_rate=0.5, queue_cap=4)


def _digest(state) -> str:
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(state):
        h.update(jax.device_get(leaf).tobytes())
    return h.hexdigest()[:16]


def _xla_final(cfg, n_ticks=32):
    return run_chunk(
        init_state(cfg), base_key(cfg), init_plan(cfg), cfg.fault, n_ticks,
        get_step_fn(cfg.protocol),
    )


def _ctr_final(cfg, n_ticks=32):
    from paxos_tpu.kernels.fused_tick import fused_fns, reference_chunk

    apply_fn, mask_fn, _ = fused_fns(cfg.protocol)
    return reference_chunk(
        init_state(cfg), cfg.seed, init_plan(cfg), cfg.fault, n_ticks,
        apply_fn=apply_fn, mask_fn=mask_fn, blk_id=0,
    )


# The established goldens (tests/test_margin.py, n_inst=256, seed=7,
# 32 ticks, CPU): workload-off must reproduce them, and workload-ON minus
# the queue leaf must reproduce them too (the arrival draw rides its own
# registered stream — the protocol schedule never moves, both engines).
_GOLDEN_XLA = {
    "config2": (lambda: C.config2_dueling_drop(256, 7), "83347bc41b16a2aa"),
    "config3": (lambda: C.config3_multipaxos(256, 7), "93a2dd9d7b8d66e4"),
}
_GOLDEN_CTR = {
    "config2": (lambda: C.config2_dueling_drop(256, 7), "db6db6f40f16eb7b"),
    "config3": (lambda: C.config3_multipaxos(256, 7), "4b6525460815d9c5"),
}

_FAST = ("config2",)


@pytest.mark.parametrize(
    "name",
    [
        n if n in _FAST else pytest.param(n, marks=pytest.mark.slow)
        for n in sorted(_GOLDEN_XLA)
    ],
)
def test_workload_on_schedule_identical_xla(name):
    mk, want = _GOLDEN_XLA[name]
    assert _digest(_xla_final(mk())) == want  # off == the pinned golden
    fin = _xla_final(dataclasses.replace(mk(), workload=WL))
    assert fin.wload is not None
    assert int(jnp.sum(fin.wload.offered)) > 0  # the plane actually ran
    assert _digest(fin.replace(wload=None)) == want  # on == same schedule


@pytest.mark.parametrize(
    "name",
    [
        n if n in _FAST else pytest.param(n, marks=pytest.mark.slow)
        for n in sorted(_GOLDEN_CTR)
    ],
)
def test_workload_on_schedule_identical_counter_stream(name):
    mk, want = _GOLDEN_CTR[name]
    assert _digest(_ctr_final(mk())) == want
    fin = _ctr_final(dataclasses.replace(mk(), workload=WL))
    assert int(jnp.sum(fin.wload.offered)) > 0
    assert _digest(fin.replace(wload=None)) == want


def test_default_off_prunes_to_none():
    """A disabled workload leaves NO trace in the pytree; enabled, every
    queue leaf is non-scalar int32 instance-minor so the fused engine's
    generic passthrough codec rides them with zero layout-table edits."""
    for mk in (C.config1_no_faults, C.config3_multipaxos):
        cfg = mk(64, 0)
        state = init_state(cfg)
        assert state.wload is None
        assert not cfg.workload.enabled()
        on = init_state(dataclasses.replace(cfg, workload=WL))
        off_n = len(jax.tree_util.tree_leaves(state))
        on_n = len(jax.tree_util.tree_leaves(on))
        assert on_n == off_n + 10  # mode/phase/ring/head/depth/peak/
        #   offered/done/shed/hist — cfg rides as static treedef aux data
        for leaf in jax.tree_util.tree_leaves(on.wload):
            assert leaf.dtype == jnp.int32
            assert leaf.shape[-1] == 64
            assert leaf.ndim >= 2  # no scalars (codec contract)


def test_fingerprint_unchanged_by_default_workload():
    """The default (off) WorkloadConfig is dropped from the fingerprint so
    pre-workload artifacts keep matching; a non-default one IS keyed."""
    cfg = C.config2_dueling_drop(1 << 10)
    assert (
        dataclasses.replace(
            cfg, workload=gen.WorkloadConfig()
        ).fingerprint()
        == cfg.fingerprint()
    )
    assert (
        dataclasses.replace(cfg, workload=WL).fingerprint()
        != cfg.fingerprint()
    )


def test_workload_config_validation():
    gen.WorkloadConfig().validate()  # off: everything permissible
    WL.validate()
    with pytest.raises(ValueError, match="mix"):
        gen.WorkloadConfig(mix="sinusoid").validate()
    with pytest.raises(ValueError, match="rate"):
        gen.WorkloadConfig(mix="poisson", rate=1.5).validate()
    with pytest.raises(ValueError, match="queue_cap"):
        gen.WorkloadConfig(mix="poisson", queue_cap=0).validate()
    with pytest.raises(ValueError, match="burst_len"):
        gen.WorkloadConfig(mix="bursty", period=8, burst_len=9).validate()


def test_arrival_plan_golden():
    """The once-per-campaign plan sample (class + phase per lane) is pinned
    — it rides the dedicated ROOT_WLOAD lineage, so neither the step nor
    the fault-plan lineage can shift it (and vice versa)."""
    wl = gen.WloadState.init(256, 2, WL, 7)
    mode = np.asarray(jax.device_get(wl.mode))
    phase = np.asarray(jax.device_get(wl.phase))
    h = hashlib.sha256()
    h.update(mode.tobytes())
    h.update(phase.tobytes())
    assert h.hexdigest()[:16] == "a719554e33bedb9d"
    assert np.bincount(mode.ravel()).tolist() == [185, 170, 157]
    assert mode[0, :6].tolist() == [0, 1, 2, 2, 2, 1]
    assert phase[0, :6].tolist() == [19, 26, 9, 10, 20, 29]
    # A pinned mix pins every lane; phases still spread.
    wb = gen.WloadState.init(64, 2, gen.WorkloadConfig(mix="bursty"), 3)
    assert np.asarray(jax.device_get(wb.mode)).max() == 1
    assert np.asarray(jax.device_get(wb.mode)).min() == 1


def test_arrival_threshold_modulation():
    """The numpy twin's per-class thresholds, pinned: Poisson constant,
    bursty high exactly inside the window, diurnal triangle between the
    rates — and the device fold agrees bit for bit at every tick."""
    cfg = gen.WorkloadConfig(mix="mixed", rate=0.25, burst_rate=0.75)
    mode = np.array([[0, 1, 2]])
    phase = np.zeros((1, 3), np.int64)
    assert gen.rate_to_threshold(0.25) == 1 << 30
    assert gen.rate_to_threshold(1.0) == (1 << 32) - 1
    assert gen.np_arrival_threshold(cfg, mode, phase, 0).tolist() == [
        [1073741824, 3221225472, 1073741824]
    ]
    assert gen.np_arrival_threshold(cfg, mode, phase, 8).tolist() == [
        [1073741824, 1073741824, 2147483648]  # burst over; diurnal mid
    ]
    assert gen.np_arrival_threshold(cfg, mode, phase, 16).tolist() == [
        [1073741824, 1073741824, 3221225472]  # diurnal crest
    ]
    wl = gen.WloadState.init(3, 1, cfg, 0).replace(
        mode=jnp.asarray(mode, jnp.int32),
        phase=jnp.zeros((1, 3), jnp.int32),
    )
    for t in range(2 * cfg.period):
        dev = np.asarray(
            jax.device_get(gen.arrival_threshold(wl, jnp.int32(t)))
        ).view(np.uint32)
        host = gen.np_arrival_threshold(cfg, mode, phase, t)
        assert np.array_equal(dev, host), f"tick {t}"


# ---------------------------------------------------------------------------
# The oracle: re-derive every arrival bit from the stream registry (never
# from the device), extract the per-tick serve edges, and replay the whole
# queue in numpy — final leaves must match the device bit for bit.

_ORACLE_TICKS = 256


def _oracle_cfg(protocol):
    base = (
        C.config3_multipaxos(128, 11)
        if protocol == "multipaxos"
        else dataclasses.replace(
            C.config2_dueling_drop(128, 11), protocol=protocol
        )
    )
    return dataclasses.replace(base, workload=WL)


def _arrival_bits(engine, cfg, tick, shape):
    """The tick's raw arrival bits, recomputed from the registry alone."""
    if engine == "xla":
        k = streams_mod.tick_key(base_key(cfg), jnp.int32(tick))
        k = streams_mod.tick_fold(k, "ARRIVAL_BITS")
        bits = jax.random.bits(k, shape, jnp.uint32)
        return np.asarray(jax.device_get(bits)).astype(np.uint32)
    from paxos_tpu.kernels import counter_prng as cp

    sid = streams_mod.family_of(cfg.protocol).streams["ARRIVAL"]
    seed_t = cp.mix(jnp.int32(cfg.seed), jnp.int32(tick), jnp.int32(0))
    bits = cp.counter_bits(seed_t, sid, shape)
    return np.asarray(jax.device_get(bits)).view(np.uint32)


@pytest.mark.parametrize(
    "engine,protocol",
    [
        ("xla", "paxos"),
        ("ctr", "paxos"),
        pytest.param("xla", "multipaxos", marks=pytest.mark.slow),
        pytest.param("ctr", "multipaxos", marks=pytest.mark.slow),
        pytest.param("xla", "fastpaxos", marks=pytest.mark.slow),
        pytest.param("ctr", "raftcore", marks=pytest.mark.slow),
        pytest.param("xla", "synchpaxos", marks=pytest.mark.slow),
    ],
)
def test_queue_vs_numpy_replay(engine, protocol):
    cfg = _oracle_cfg(protocol)
    plan = init_plan(cfg)
    state = init_state(cfg)
    if engine == "xla":
        key = base_key(cfg)
        step = get_step_fn(cfg.protocol)

        @jax.jit
        def advance(st):
            return run_chunk(st, key, plan, cfg.fault, 1, step)
    else:
        from paxos_tpu.kernels.fused_tick import fused_fns, reference_chunk

        apply_fn, mask_fn, _ = fused_fns(cfg.protocol)
        seed = jnp.int32(cfg.seed)

        @jax.jit
        def advance(st):
            return reference_chunk(
                st, seed, plan, cfg.fault, 1,
                apply_fn=apply_fn, mask_fn=mask_fn,
            )

    mode = np.asarray(jax.device_get(state.wload.mode))
    phase = np.asarray(jax.device_get(state.wload.phase))
    shape = mode.shape
    arrivals = np.zeros((_ORACLE_TICKS,) + shape, bool)
    pops = np.zeros((_ORACLE_TICKS,) + shape, bool)
    prev_off = np.zeros(shape, np.int64)
    prev_done = np.zeros(shape, np.int64)
    for t in range(_ORACLE_TICKS):
        arrivals[t] = _arrival_bits(
            engine, cfg, t, shape
        ) < gen.np_arrival_threshold(WL, mode, phase, t)
        state = advance(state)
        off = np.asarray(jax.device_get(state.wload.offered), np.int64)
        done = np.asarray(jax.device_get(state.wload.done), np.int64)
        # The offered counter IS the arrival process: the device must have
        # sampled exactly the arrivals the registry math predicts.
        assert np.array_equal(off - prev_off, arrivals[t]), f"tick {t}"
        pops[t] = done - prev_done
        prev_off, prev_done = off, done

    replay = gen.np_replay_queue(WL, mode, arrivals, pops)
    dev = jax.device_get(state.wload)
    for name in ("head", "depth", "depth_peak", "offered", "done", "shed",
                 "hist"):
        assert np.array_equal(
            replay[name], np.asarray(getattr(dev, name), np.int64)
        ), name
    # The campaign must actually exercise the interesting paths.
    assert replay["done"].sum() > 0
    assert replay["shed"].sum() > 0  # cap-4 ring under 0.2-0.5 load sheds
    assert replay["hist"].sum() == replay["done"].sum()


@pytest.mark.parametrize(
    "protocol",
    [
        "paxos",
        pytest.param("multipaxos", marks=pytest.mark.slow),
        pytest.param("fastpaxos", marks=pytest.mark.slow),
        pytest.param("raftcore", marks=pytest.mark.slow),
        pytest.param("synchpaxos", marks=pytest.mark.slow),
    ],
)
def test_fused_kernel_carries_workload_bitexact(protocol):
    """fused_chunk(interpret) == reference_chunk with the queue ON: the
    packed-word passthrough codec must round-trip all ten leaves."""
    from paxos_tpu.kernels.fused_tick import (
        FUSED_CHUNKS,
        fused_fns,
        reference_chunk,
    )
    from paxos_tpu.utils.trees import tree_mismatches

    cfg = dataclasses.replace(
        C.config2_dueling_drop(64, 7), protocol=protocol, workload=WL
    )
    apply_fn, mask_fn, _ = fused_fns(cfg.protocol)
    plan = init_plan(cfg)
    sr = reference_chunk(
        init_state(cfg), jnp.int32(cfg.seed), plan, cfg.fault, 24,
        apply_fn=apply_fn, mask_fn=mask_fn,
    )
    sp = FUSED_CHUNKS[cfg.protocol](
        init_state(cfg), jnp.int32(cfg.seed), plan, cfg.fault, 24,
        block=64, interpret=True,
    )
    assert tree_mismatches(sp, sr) == []
    assert int(jnp.sum(sr.wload.offered)) > 0


# ---------------------------------------------------------------------------
# SLO reductions, report plumbing, checkpoint round-trip.


def test_percentiles_and_breach():
    """Log2-bucket percentiles report the bucket's inclusive upper edge,
    -1 when nothing served; breach gating ignores unserved classes."""
    assert slo_mod._percentile_ticks([0, 0, 0], 99) == -1
    assert slo_mod._percentile_ticks([10, 0, 0], 50) == 1  # bucket 0: [1,1]
    assert slo_mod._percentile_ticks([10, 0, 0], 99) == 1
    # 90 in bucket 0, 10 in bucket 2 ([4,7]): p90 still bucket 0, p95 jumps
    assert slo_mod._percentile_ticks([90, 0, 10], 90) == 1
    assert slo_mod._percentile_ticks([90, 0, 10], 95) == 7
    report = {
        "classes": {
            "poisson": {"done": 5, "p99_ticks": 3},
            "bursty": {"done": 8, "p99_ticks": 31},
            "diurnal": {"done": 0, "p99_ticks": -1},
        }
    }
    assert slo_mod.slo_breach(report, 0) == []  # no SLO configured
    assert slo_mod.slo_breach(report, 7) == ["bursty"]
    assert slo_mod.slo_breach(report, 31) == []


def test_overload_knee():
    pts = [
        {"rate_scale": 0.5, "offered": 100, "done": 99},
        {"rate_scale": 1.0, "offered": 200, "done": 190},
        {"rate_scale": 2.0, "offered": 400, "done": 250},
        {"rate_scale": 4.0, "offered": 800, "done": 260},
    ]
    knee = slo_mod.overload_knee(pts, floor=0.9)
    assert knee["rate_scale"] == 2.0
    assert knee["goodput"] == 250 / 400
    assert slo_mod.overload_knee(pts[:2], floor=0.9) is None
    assert slo_mod.overload_knee([{"offered": 0, "done": 0}]) is None


def test_slo_merge_recomputes_percentiles():
    """Cross-seed merge sums counters and histograms, then RECOMPUTES the
    percentiles — an average of percentiles is not a percentile."""
    a = {
        "classes": {"bursty": {
            "lanes": 4, "offered": 10, "done": 10, "shed": 0,
            "hist": [10, 0, 0], "p99_ticks": 1,
        }},
        "queue_depth": 2, "depth_peak": 3, "p99_ticks": 1,
    }
    b = {
        "classes": {"bursty": {
            "lanes": 4, "offered": 12, "done": 6, "shed": 6,
            "hist": [0, 0, 6], "p99_ticks": 7,
        }},
        "queue_depth": 5, "depth_peak": 4, "p99_ticks": 7,
    }
    m = slo_mod.slo_merge([a, b])
    row = m["classes"]["bursty"]
    assert row["offered"] == 22 and row["done"] == 16 and row["shed"] == 6
    assert row["hist"] == [10, 0, 6]
    assert row["p50_ticks"] == 1  # 10 of 16 in bucket 0
    assert row["p99_ticks"] == 7  # the merged tail, not mean(1, 7)
    assert m["goodput"] == 16 / 22
    assert m["queue_depth"] == 5  # point-in-time: last block wins
    assert m["depth_peak"] == 4  # high-water mark: max wins
    assert m["p99_ticks"] == 7


def test_run_report_embeds_slo_block():
    """A workload-on run report carries the full SLO block; off, no key."""
    cfg = dataclasses.replace(C.config2_dueling_drop(64, 3), workload=WL)
    rep = run(cfg, total_ticks=64, chunk=32)
    slo = rep["slo"]
    assert slo["offered"] > 0
    assert set(slo["classes"]) == set(gen.CLASSES)
    assert slo["offered"] == sum(
        r["offered"] for r in slo["classes"].values()
    )
    assert 0.0 <= slo["goodput"] <= 1.0
    # queue_depth sums the live backlog over all lanes; depth_peak is the
    # per-lane high-water mark, clamped by the ring capacity.
    assert slo["queue_depth"] >= 0
    assert 0 < slo["depth_peak"] <= WL.queue_cap
    served = [r for r in slo["classes"].values() if r["done"] > 0]
    assert served, "64 ticks at rate 0.2 must serve something"
    for r in served:
        assert r["p50_ticks"] <= r["p95_ticks"] <= r["p99_ticks"]
        assert sum(r["hist"]) == r["done"]
    rep_off = run(C.config2_dueling_drop(64, 3), total_ticks=16, chunk=8)
    assert "slo" not in rep_off


def test_checkpoint_roundtrip_with_workload(tmp_path):
    """Save/restore rebuilds the workload config AND the queue arrays, so
    a resumed campaign's SLO accounting is bit-identical."""
    cfg = dataclasses.replace(C.config2_dueling_drop(64, 3), workload=WL)
    step = get_step_fn(cfg.protocol)
    key, plan = base_key(cfg), init_plan(cfg)
    state = run_chunk(init_state(cfg), key, plan, cfg.fault, 16, step)
    checkpoint.save(tmp_path / "ck", state, plan, cfg, engine="xla")
    st2, pl2, cfg2 = checkpoint.restore(tmp_path / "ck", engine="xla")
    assert cfg2.workload == WL
    assert st2.wload is not None
    assert st2.wload.cfg == WL  # the static knob carrier restored too
    fin_a = run_chunk(state, key, plan, cfg.fault, 16, step)
    fin_b = run_chunk(st2, base_key(cfg2), pl2, cfg2.fault, 16, step)
    assert _digest(fin_a) == _digest(fin_b)  # queue leaves included


def test_checkpoint_restore_pre_workload_snapshot(tmp_path):
    """Snapshots written before the workload plane (no key in the JSON)
    restore with the default-off config and a pruned leaf."""
    cfg = C.config2_dueling_drop(64, 3)
    checkpoint.save(tmp_path / "ck", init_state(cfg), init_plan(cfg), cfg)
    meta_path = tmp_path / "ck" / "simconfig.json"
    raw = json.loads(meta_path.read_text())
    raw.pop("workload")
    meta_path.write_text(json.dumps(raw))
    st2, _, cfg2 = checkpoint.restore(tmp_path / "ck")
    assert cfg2.workload == gen.WorkloadConfig()
    assert st2.wload is None
